# In-program A/B of weight-only int8 serving (W8A16,
# layers.quantize_linear_tree) at the bench's llama geometry: 1b bf16,
# 256 slots, closed loop.  Decode serving streams the full weight set
# every step (2.47 GB of the ~4.6 GB step read), so halving weight
# bytes is the largest single lever left after the r5 block-KV scan —
# IF the int8 convert fuses in the real program the way the isolated
# probes (tools/diag_attn_patterns.py mha1q) and the cross-KV fold
# (tools/ab_cross_kv.py) measured.
#
# Prints tok/s + pure-device chained step time per mode, plus greedy
# token parity on a fixed prompt set.

from __future__ import annotations

import dataclasses
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from aiko_services_tpu.models.llama import (  # noqa: E402
    LLAMA_PRESETS, llama_init)
from aiko_services_tpu.serving import ContinuousDecoder  # noqa: E402

SLOTS = 256
WINDOW = float(os.environ.get("AB_W8_WINDOW", "20"))


def build(params, config, weight_quant):
    return ContinuousDecoder(params, config, max_slots=SLOTS,
                             max_seq=1024, prefill_buckets=(128,),
                             steps_per_sync=64,
                             weight_quant=weight_quant,
                             name=f"w8_{int(weight_quant)}")


def closed_loop(decoder, rng):
    generated = [0]
    submitted = [0]
    deadline = [time.perf_counter() + 3600.0]

    def submit_one():
        prompt = rng.integers(
            1, decoder.config.vocab,
            size=int(rng.integers(16, 120))).tolist()
        request_id = f"r{submitted[0]}"
        submitted[0] += 1
        decoder.submit(request_id, prompt, 64,
                       lambda rid, tokens: on_done(tokens))

    def on_done(tokens):
        generated[0] += len(tokens)
        if time.perf_counter() < deadline[0]:
            submit_one()

    for _ in range(2 * SLOTS):          # warmup: compile + fill
        submit_one()
    decoder.pump()
    # same post-warmup reset protocol as bench.bench_llama (the
    # canonical closed-loop methodology this tool mirrors): compile
    # time must not contaminate stats or SLO percentiles
    for key in decoder.stats:
        decoder.stats[key] = 0 if isinstance(decoder.stats[key], int)             else 0.0
    decoder.ttft_samples.clear()
    decoder.itl_samples.clear()
    decoder.gap_samples.clear()
    generated[0] = 0
    start = time.perf_counter()
    deadline[0] = start + WINDOW
    while time.perf_counter() < deadline[0] or not decoder.idle:
        decoder.pump()
        if decoder.idle and time.perf_counter() >= deadline[0]:
            break
    elapsed = time.perf_counter() - start
    return generated[0] / elapsed


def device_step(decoder, steps_per_sync=64, chains=4):
    """Chained pure-device step time, same method as the bench's
    llama_device_step_ms probe (fresh buffers at the serving shape,
    one sync for the whole chain)."""
    config = decoder.config
    try:
        t_cache = decoder._cache_t
        shape = (SLOTS, config.num_kv_heads, t_cache, config.head_dim)
        k_probe = [jnp.zeros(shape, config.dtype)
                   for _ in range(config.num_layers)]
        v_probe = [jnp.zeros(shape, config.dtype)
                   for _ in range(config.num_layers)]
        tokens = jnp.ones((SLOTS,), jnp.int32)
        lengths = jnp.zeros((SLOTS,), jnp.int32)
        active = jnp.ones((SLOTS,), bool)
        budgets = jnp.full((SLOTS,), 1 << 30, jnp.int32)

        def chain(rounds):
            nonlocal k_probe, v_probe, tokens, lengths
            out = None
            for _ in range(rounds):
                out = decoder._step(decoder.params, tokens, lengths,
                                    active, budgets, k_probe, v_probe,
                                    num_steps=steps_per_sync, eos=-1)
                _, _, _, tokens, lengths, k_probe, v_probe = out
            np.asarray(out[0][-1])
        chain(1)
        start = time.perf_counter()
        chain(chains)
        return (time.perf_counter() - start) * 1000.0 / \
            (chains * steps_per_sync)
    except Exception as exc:
        print(f"device-step probe failed: {exc!r}", file=sys.stderr)
        return None


def parity(params, config, n=32):
    """Greedy outputs for n fixed prompts under both modes."""
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, config.vocab,
                            size=int(rng.integers(8, 100))).tolist()
               for _ in range(n)]
    outs = {}
    for wq in (False, True):
        decoder = build(params, config, wq)
        done = {}
        for i, prompt in enumerate(prompts):
            decoder.submit(f"p{i}", prompt, 32,
                           lambda rid, toks, i=i: done.setdefault(i,
                                                                  toks))
        for _ in range(600):
            if len(done) == n:
                break
            decoder.pump()
        assert len(done) == n, f"only {len(done)}/{n} completed"
        outs[wq] = done
        del decoder
    total = match = 0
    for i in range(n):
        a, b = outs[False][i], outs[True][i]
        k = min(len(a), len(b))
        match += sum(x == y for x, y in zip(a[:k], b[:k]))
        total += k
    return match / max(total, 1)


def main():
    base = LLAMA_PRESETS[os.environ.get("AB_W8_PRESET", "1b")]
    config = dataclasses.replace(base, dtype=jnp.bfloat16,
                                 max_seq_len=1024)
    params = llama_init(jax.random.PRNGKey(0), config)

    for wq in (False, True):
        decoder = build(params, config, wq)
        tps = closed_loop(decoder, np.random.default_rng(11))
        step_ms = device_step(decoder)
        print(f"weight_quant={wq}: {tps:,.0f} tok/s"
              + (f", device step {step_ms:.2f} ms"
                 if step_ms is not None else ""), flush=True)
        del decoder

    print(f"token parity (32 fixed prompts, 32 tokens): "
          f"{parity(params, config):.4f}", flush=True)


if __name__ == "__main__":
    main()
