# experiment harness: the console readout is the product
# graft: disable-file=lint-print
# Vocoder data-scaling experiment (r5, the residual of VERDICT r4 item
# 8): the vocoder measured 23.88 dB held-out MCD vs Griffin-Lim-32's
# 22.72, and the preset note recorded that model scaling plateaued —
# "scale past this needs more training data, not more parameters".
# Training data is SYNTHETIC (tests/test_speech_golden.py tones), so
# more is free: widening 8 → 29 train utterances (every 1-3-word
# sequence without the held-out adjacency) at the SAME geometry
# measured 21.10 dB — past GL-32 — while bigger geometries still
# overfit (26.8 / 28.8).  That wide corpus is now the canonical
# recipe in tests/test_tts.py::train_vocoder; this tool re-runs the
# sweep that established it by calling the SAME trainer with corpus /
# geometry overrides (no duplicated recipe to drift).
#
# Run ON the TPU (training is ~2 min/config there, ~hours on the
# 1-core CPU):  python tools/train_vocoder_scale.py

from __future__ import annotations

import itertools
import sys
import time

sys.path.insert(0, ".")
sys.path.insert(0, "tests")

import test_speech_golden as asr_golden  # noqa: E402
import test_tts  # noqa: E402
from aiko_services_tpu.models.vocoder import VocoderConfig  # noqa: E402

HELD_OUT = ["alpha", "charlie"]


def base_corpus():
    texts = [["alpha"], ["bravo"], ["charlie"],
             ["alpha", "bravo"], ["bravo", "charlie"],
             ["charlie", "alpha"], ["alpha", "charlie"],
             ["bravo", "alpha"], ["charlie", "bravo"]]
    return [t for t in texts if t != HELD_OUT]


def leaks(seq):
    return any(list(seq[i:i + len(HELD_OUT)]) == HELD_OUT
               for i in range(len(seq) - len(HELD_OUT) + 1))


def wide_corpus():
    texts = base_corpus()
    for seq in itertools.product(sorted(asr_golden.WORDS), repeat=3):
        if not leaks(seq):
            texts.append(list(seq))
    return texts


def held_out_mcd(params, config):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from aiko_services_tpu.ops.audio import (log_mel_spectrogram,
                                             mel_cepstral_distortion)
    from aiko_services_tpu.models.vocoder import vocoder_forward

    mel_fn = jax.jit(log_mel_spectrogram)
    wave_true = np.asarray(asr_golden.utterance(HELD_OUT), np.float32)
    mel_true = np.asarray(mel_fn(wave_true[None]))[0]
    audio = np.asarray(vocoder_forward(params, config,
                                       jnp.asarray(mel_true[None])))[0]
    mel_out = np.asarray(mel_fn(audio[None].astype(np.float32)))[0]
    frames = min(mel_out.shape[0], mel_true.shape[0])
    return mel_cepstral_distortion(mel_out[:frames], mel_true[:frames])


def main():
    import jax
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    runs = [
        ("base8", base_corpus(), VocoderConfig(channels=(96, 48, 24),
                                               basis=64), 6000, 64),
        ("wide", wide_corpus(), VocoderConfig(channels=(96, 48, 24),
                                              basis=64), 9000, 96),
        ("wide", wide_corpus(), VocoderConfig(channels=(128, 64, 32),
                                              basis=64), 9000, 96),
        ("wide", wide_corpus(), VocoderConfig(channels=(192, 96, 48),
                                              basis=96), 9000, 96),
    ]
    for name, texts, config, steps, window in runs:
        t0 = time.perf_counter()
        params, config = test_tts.train_vocoder(
            HELD_OUT, vocoder_config=config, texts=texts, steps=steps,
            window=window)
        mcd = held_out_mcd(params, config)
        print(f"{name:6s} ({len(texts):2d} utts) "
              f"channels={config.channels} basis={config.basis} "
              f"steps={steps} held-out MCD={mcd:.2f} dB "
              f"({time.perf_counter() - t0:.0f}s)", flush=True)
    print("reference: GL-16 31.58; GL-32 22.72; pre-r5 vocoder 23.88",
          flush=True)


if __name__ == "__main__":
    main()
