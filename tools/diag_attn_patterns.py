# diagnostic harness: the console readout is the product
# graft: disable-file=lint-print
# Which decode-attention pattern reaches this chip's real bandwidth
# ceiling, and does int8 KV with a PURE CONVERT dequant (per-tensor
# scale folded into the softmax scale) fuse into the dot?
#
# Measurement discipline (hard-won, see .claude/skills/verify): the
# tunnel costs ~108 ms per dispatch+sync ROUND TRIP — any program
# shorter than ~1 s measures the tunnel.  Each pattern therefore runs
# at TWO in-program rep counts (fori_loop feeding attention output
# back into the query) and reports the marginal rate
# (T_hi - T_lo) / (reps_hi - reps_lo): dispatch floor and compile-time
# constants cancel exactly, like the slope method that diagnosed the
# llama decode scan.
#
# Patterns (raw streaming-read ceiling: tools/diag_membw.py):
#   gqa4   — llama serving shape [S,8,G=4,1,64]x[S,8,T,64]
#   mha1   — whisper decode shape [B,12,1,64]x[B,12,T,64]
#   mha8   — whisper shape, 8 packed queries (is M=1 the limiter?)
#   mha1q  — mha1 with int8 K/V and pure-astype dequant (half bytes)

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from diag_membw import marginal_rate  # noqa: E402  shared 2-point harness


def main():
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    # raw streaming-read ceiling: see tools/diag_membw.py (slicesum /
    # matvec).  An additive-taint sum probe lived here first and
    # printed 5 TB/s — XLA rewrote sum(x + c) to sum(x) + N*c and
    # hoisted the loop-invariant sum(x); carry-fed consumers only.

    def attn_builder(einsum_a, einsum_b, k_scale=None,
                     v_scale=None):
        def build(reps):
            def f(q0, k, v):
                def body(i, q):
                    kk = k.astype(jnp.bfloat16) if k.dtype == jnp.int8 \
                        else k
                    vv = v.astype(jnp.bfloat16) if v.dtype == jnp.int8 \
                        else v
                    scores = jnp.einsum(
                        einsum_a, q, kk,
                        preferred_element_type=jnp.float32)
                    if k_scale is not None:
                        scores = scores * k_scale
                    w = jax.nn.softmax(scores, axis=-1).astype(
                        jnp.bfloat16)
                    out = jnp.einsum(
                        einsum_b, w, vv,
                        preferred_element_type=jnp.float32)
                    if v_scale is not None:
                        out = out * v_scale
                    return out.astype(jnp.bfloat16)
                return jnp.sum(jax.lax.fori_loop(0, reps, body, q0),
                               dtype=jnp.float32)
            return f
        return build

    # gqa4: llama 1b serving shape
    s, hkv, g, d, t = 256, 8, 4, 64, 2048
    k = jnp.ones((s, hkv, t, d), jnp.bfloat16)
    v = jnp.ones((s, hkv, t, d), jnp.bfloat16)
    q0 = jnp.ones((s, hkv, g, 1, d), jnp.bfloat16)
    marginal_rate("gqa4",
                  attn_builder("skgqd,sktd->skgqt",
                               "skgqt,sktd->skgqd"),
                  k.nbytes + v.nbytes, q0, k, v)
    del k, v, q0

    # whisper decode shape
    b, h, t, d = 256, 12, 2048, 64
    k = jnp.ones((b, h, t, d), jnp.bfloat16)
    v = jnp.ones((b, h, t, d), jnp.bfloat16)
    for num_q in (1, 8):
        q0 = jnp.ones((b, h, num_q, d), jnp.bfloat16)
        marginal_rate(f"mha{num_q}",
                      attn_builder("bhqd,bhtd->bhqt",
                                   "bhqt,bhtd->bhqd"),
                      k.nbytes + v.nbytes, q0, k, v)
    del k, v

    ki = jnp.ones((b, h, t, d), jnp.int8)
    vi = jnp.ones((b, h, t, d), jnp.int8)
    q0 = jnp.ones((b, h, 1, d), jnp.bfloat16)
    marginal_rate("mha1q",
                  attn_builder("bhqd,bhtd->bhqt", "bhqt,bhtd->bhqd",
                               k_scale=jnp.float32(1.0 / 127.0),
                               v_scale=jnp.float32(1.0 / 127.0)),
                  ki.nbytes + vi.nbytes, q0, ki, vi)


if __name__ == "__main__":
    main()
