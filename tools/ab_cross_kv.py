# A/B harness: the console comparison table is the product
# graft: disable-file=lint-print
# In-program A/B of the cross-KV modes at the bench's chip geometry
# (whisper-small bf16, batch 256, 5 s chunks, 24 tokens): bf16 vs
# int8 per-position (r4's memory lever, measured −24%) vs int8
# per-tensor (r5: scalar scale folded into the softmax scale so the
# dequant is a pure convert — 38% faster in ISOLATION, and the verify
# notes demand the in-program number before believing it).
#
# Prints round ms + device-resident streams per mode and greedy-token
# parity vs the bf16 program.

from __future__ import annotations

import dataclasses
import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from aiko_services_tpu.models import whisper_init  # noqa: E402
from aiko_services_tpu.models.whisper import (  # noqa: E402
    WHISPER_PRESETS, encode, greedy_decode_from_audio)
from aiko_services_tpu.ops.audio import (  # noqa: E402
    WHISPER_HOP, log_mel_spectrogram, mulaw_decode)

BATCH = 256
MAX_TOKENS = 24


from diag_membw import timed_chain as timed  # noqa: E402  shared harness


def main():
    config = dataclasses.replace(
        WHISPER_PRESETS["small"], n_audio_ctx=250,
        n_text_ctx=MAX_TOKENS + 8, dtype=jnp.bfloat16)
    params = whisper_init(jax.random.PRNGKey(0), config)
    samples = config.n_audio_ctx * 2 * WHISPER_HOP
    codes = jax.random.randint(jax.random.PRNGKey(2), (BATCH, samples),
                               0, 256, jnp.int32).astype(jnp.uint8)

    def fused(mode):
        def f(params, pcm):
            audio = mulaw_decode(pcm)
            mel = log_mel_spectrogram(audio, num_mels=config.n_mels)
            return greedy_decode_from_audio(
                params, config,
                encode(params, config, mel.astype(config.dtype)),
                max_tokens=MAX_TOKENS, kv_quant=mode)
        return f

    results = {}
    for mode in (False, "position", "tensor"):
        compiled = jax.jit(fused(mode)).lower(params, codes).compile()
        seconds = timed(compiled, params, codes)
        out = compiled(params, codes)
        tokens, lengths = np.asarray(out[0]), np.asarray(out[1])
        results[mode] = (seconds, tokens, lengths)
        streams = BATCH * 5.0 / seconds
        print(f"mode {str(mode):8s}: round {seconds * 1e3:7.1f} ms -> "
              f"{streams:6.0f} streams", flush=True)

    # mask by decoded lengths, matching the bench A/B exactly —
    # post-EOS padding would otherwise inflate the match rate
    _, base, base_len = results[False]
    for mode in ("position", "tensor"):
        seconds, tokens, lengths = results[mode]
        valid = np.arange(base.shape[1])[None, :] < \
            np.minimum(base_len, lengths)[:, None]
        match = (tokens == base)[valid].mean() if valid.any() else 1.0
        delta = seconds / results[False][0] - 1.0
        print(f"mode {mode:8s}: token match {match:.4f}, "
              f"round delta {delta:+.1%}", flush=True)


if __name__ == "__main__":
    main()
