# diagnostic harness: the console readout is the product
# graft: disable-file=lint-print
# What HBM streaming bandwidth can THIS chip actually reach?  The
# 819 GB/s v5e spec is the roofline denominator the bench uses;
# "bandwidth-bound" claims are only meaningful against the best
# ACHIEVABLE number, which this probe measures.
#
# Two hard-won measurement rules (.claude/skills/verify/SKILL.md):
#   1. One dispatch+sync through the axon tunnel costs ~108 ms even
#      for a 3 ms kernel — every pattern runs at TWO in-program rep
#      counts and reports the marginal rate
#      (T_hi - T_lo) / (reps_hi - reps_lo); the dispatch floor and
#      compile constants cancel exactly.
#   2. XLA's algebraic simplifier sees through additive taints:
#      sum(x + c) becomes sum(x) + N*c with sum(x) hoisted out of the
#      loop (a first version of this tool printed 5 TB/s that way).
#      Each iteration's read must therefore depend on the carry
#      through its ACTUAL consumer: the slice offset of the read, or
#      the operand fed back from the previous result — and inputs are
#      random, never jnp.ones (constants can fold entirely).
#
# Patterns:
#   slicesum — sum over a carry-offset dynamic_slice window of a 1 GiB
#              random array: pure streaming read, unfoldable
#   matvec   — [M, 4096] @ v with v fed back from the result: an
#              MXU-issued streaming read
#
# For the decode-attention shapes (the numbers that matter for the
# whisper/llama tails) see tools/diag_attn_patterns.py.

from __future__ import annotations

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

REPS_LO, REPS_HI = 64, 256


def timed(compiled, *args, repeats=5):
    np.asarray(compiled(*args))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(compiled(*args))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def timed_chain(fn, *args, chain=4, repeats=5):
    """Median per-call wall seconds with `chain` back-to-back calls per
    forced host-transfer sync — the queue-full amortization for
    100 ms+ programs (for sub-100 ms programs use the two-point rep
    fit below instead; the ~108 ms dispatch floor still leaks
    floor/chain into each measurement).  Shared by ab_cross_kv.py and
    diag_whisper_tail.py so the timing discipline cannot drift."""
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        for _ in range(chain - 1):
            out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        times.append((time.perf_counter() - t0) / chain)
    return float(np.median(times))


def marginal_rate(name, build, traffic_bytes_per_rep, *args):
    t = {}
    for reps in (REPS_LO, REPS_HI):
        compiled = jax.jit(build(reps)).lower(*args).compile()
        t[reps] = timed(compiled, *args)
    dt = t[REPS_HI] - t[REPS_LO]
    gbps = traffic_bytes_per_rep * (REPS_HI - REPS_LO) / dt / 1e9
    print(f"{name:9s} {gbps:7.0f} GB/s marginal  "
          f"(lo {t[REPS_LO] * 1e3:.1f} ms, hi {t[REPS_HI] * 1e3:.1f} ms, "
          f"{traffic_bytes_per_rep / 1e9:.2f} GB/rep)", flush=True)
    return gbps


def main():
    print(f"device: {jax.devices()[0].device_kind}", flush=True)

    n = 1 << 29                                     # 1 GiB bf16
    window = n - 256
    x = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.bfloat16)

    def build_slicesum(reps):
        def f(x):
            def body(i, carry):
                offset, acc = carry
                s = jnp.sum(
                    jax.lax.dynamic_slice(x, (offset,), (window,)),
                    dtype=jnp.float32)
                # next offset depends on the DATA just read — the
                # read can be neither hoisted nor precomputed
                offset = (jnp.abs(s).astype(jnp.int32) + i) % 256
                return offset, acc + s
            _, acc = jax.lax.fori_loop(0, reps, body,
                                       (jnp.int32(0), jnp.float32(0)))
            return acc
        return f

    marginal_rate("slicesum", build_slicesum, window * 2, x)
    del x

    a = jax.random.normal(jax.random.PRNGKey(1), (1 << 18, 4096),
                          jnp.bfloat16)             # 2 GiB
    v0 = jax.random.normal(jax.random.PRNGKey(2), (4096,), jnp.bfloat16)

    def build_mv(reps):
        def f(a, v0):
            def body(i, v):
                y = jnp.einsum("md,d->m", a, v,
                               preferred_element_type=jnp.float32)
                # feed the result back as the next operand (scaled to
                # stay finite): a real data dependence per iteration
                return (y[:4096] * (1.0 / jnp.maximum(
                    jnp.max(jnp.abs(y[:4096])), 1e-6))
                    ).astype(jnp.bfloat16)
            v = jax.lax.fori_loop(0, reps, body, v0)
            return jnp.sum(v, dtype=jnp.float32)
        return f

    marginal_rate("matvec", build_mv, a.nbytes, a, v0)


if __name__ == "__main__":
    main()
