#!/usr/bin/env python3
# measurement CLI: the console readout is the product
# graft: disable-file=lint-print
"""Measure the reference's aloha-honua pass-through rate on this host.

BASELINE.md needs a MEASURED reference number (not an assumed 1.0) to
anchor `vs_baseline`.  The aloha example is one actor whose hot path is
the reference event loop's mailbox drain
(/root/reference/aiko_services/event.py:261-319: drain mailboxes, then
sleep 10 ms); its sustainable frames/sec is that loop's message
throughput.  This script drives exactly that loop — imported from the
reference tree, mosquitto-less (the transport never enters the hot
path) — with an open-loop poster thread, counts handled messages over a
fixed window, and prints one JSON line.

--ours runs the same experiment on this framework's EventEngine
mailboxes for the apples-to-apples ratio.

Usage:
    python tools/measure_reference_baseline.py [--seconds 5] [--ours]
"""

import argparse
import json
import sys
import threading
import time
import types


def load_reference_event():
    """Import aiko_services.event from the reference tree WITHOUT
    executing the package __init__ (which pulls paho/mqtt)."""
    sys.path.insert(0, "/root/reference")
    package = types.ModuleType("aiko_services")
    package.__path__ = ["/root/reference/aiko_services"]
    sys.modules["aiko_services"] = package
    import aiko_services.event as ref_event
    return ref_event


def measure_reference(seconds: float) -> dict:
    event = load_reference_event()
    handled = [0]
    stop = threading.Event()

    def handler(name, item, time_posted):
        handled[0] += 1

    event.add_mailbox_handler(handler, "aloha")

    def poster():
        # open-loop: keep the mailbox non-empty, as a busy pipeline
        # would; bounded bursts so memory stays flat
        while not stop.is_set():
            for _ in range(256):
                event.mailbox_put("aloha", ("aloha", "Pele"))
            time.sleep(0.001)

    thread = threading.Thread(target=poster, daemon=True)
    thread.start()

    def terminator():
        time.sleep(seconds)
        stop.set()
        event.terminate()

    threading.Thread(target=terminator, daemon=True).start()
    start = time.perf_counter()
    event.loop(loop_when_no_handlers=True)
    elapsed = time.perf_counter() - start
    thread.join(timeout=2.0)
    return {"which": "reference", "messages": handled[0],
            "seconds": round(elapsed, 3),
            "messages_per_sec": round(handled[0] / elapsed, 1)}


def measure_ours(seconds: float) -> dict:
    sys.path.insert(0, ".")
    from aiko_services_tpu.event import EventEngine

    engine = EventEngine()
    handled = [0]
    stop = threading.Event()

    def handler(name, item, time_posted):
        handled[0] += 1

    engine.add_mailbox_handler(handler, "aloha")

    def poster():
        while not stop.is_set():
            for _ in range(256):
                engine.mailbox_put("aloha", ("aloha", "Pele"))
            time.sleep(0.001)

    thread = threading.Thread(target=poster, daemon=True)
    thread.start()
    start = time.perf_counter()
    deadline = start + seconds
    engine.run_until(lambda: time.perf_counter() >= deadline,
                     timeout=seconds + 10)
    stop.set()
    elapsed = time.perf_counter() - start
    thread.join(timeout=2.0)
    return {"which": "aiko_services_tpu", "messages": handled[0],
            "seconds": round(elapsed, 3),
            "messages_per_sec": round(handled[0] / elapsed, 1)}


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seconds", type=float, default=5.0)
    parser.add_argument("--ours", action="store_true")
    args = parser.parse_args()
    result = measure_ours(args.seconds) if args.ours else \
        measure_reference(args.seconds)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
