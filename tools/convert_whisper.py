#!/usr/bin/env python3
# conversion CLI: progress goes to the console by design
# graft: disable-file=lint-print
"""Convert a HuggingFace Whisper checkpoint directory to this framework's
flat-npz weight scheme + tokenizer files.

Usage:
    python tools/convert_whisper.py /path/to/whisper-small out_dir/

Input directory layout (what `huggingface-cli download openai/whisper-small`
produces): model.safetensors or pytorch_model.bin, vocab.json, merges.txt.
Output: out_dir/weights.npz (keys are '/'-joined paths into the param tree
of models/whisper.py, loadable via elements.speech.load_flat_npz) and
copies of vocab.json/merges.txt for models/tokenizer.load_tokenizer.

The mapping below is name/layout translation only (torch Linear stores
[out, in], this framework stores [in, out]; torch Conv1d stores
[out, in, k] vs [k, in, out]).  Runs fully offline; torch-cpu suffices.

Reference parity: the reference's ASR element downloads faster-whisper
checkpoints at runtime (examples/speech/speech_elements.py:174-250); this
framework converts once ahead of time so serving hosts need no network.
"""

import argparse
import os
import shutil
import sys

import numpy as np


def load_state_dict(model_dir: str) -> dict:
    safetensors_path = os.path.join(model_dir, "model.safetensors")
    torch_path = os.path.join(model_dir, "pytorch_model.bin")
    if os.path.exists(safetensors_path):
        from safetensors import safe_open
        state = {}
        with safe_open(safetensors_path, framework="np") as handle:
            for key in handle.keys():
                state[key] = handle.get_tensor(key)
        return state
    if os.path.exists(torch_path):
        import torch
        state = torch.load(torch_path, map_location="cpu",
                           weights_only=True)
        return {k: v.numpy() for k, v in state.items()}
    raise FileNotFoundError(
        f"no model.safetensors or pytorch_model.bin in {model_dir}")


def _linear(out: dict, prefix: str, state: dict, hf_prefix: str,
            bias: bool = True) -> None:
    out[f"{prefix}/w"] = state[f"{hf_prefix}.weight"].T
    if bias and f"{hf_prefix}.bias" in state:
        out[f"{prefix}/b"] = state[f"{hf_prefix}.bias"]


def _layer_norm(out: dict, prefix: str, state: dict, hf_prefix: str) -> None:
    out[f"{prefix}/scale"] = state[f"{hf_prefix}.weight"]
    out[f"{prefix}/bias"] = state[f"{hf_prefix}.bias"]


def _attention(out: dict, prefix: str, state: dict, hf_prefix: str) -> None:
    _linear(out, f"{prefix}/q", state, f"{hf_prefix}.q_proj")
    _linear(out, f"{prefix}/k", state, f"{hf_prefix}.k_proj", bias=False)
    _linear(out, f"{prefix}/v", state, f"{hf_prefix}.v_proj")
    _linear(out, f"{prefix}/o", state, f"{hf_prefix}.out_proj")


def convert(state: dict) -> dict:
    state = {k.removeprefix("model."): v for k, v in state.items()}
    out = {}
    # encoder frontend: torch Conv1d [out, in, k] → [k, in, out]
    for conv in ("conv1", "conv2"):
        out[f"{conv}/w"] = state[f"encoder.{conv}.weight"].transpose(2, 1, 0)
        out[f"{conv}/b"] = state[f"encoder.{conv}.bias"]

    layer = 0
    while f"encoder.layers.{layer}.fc1.weight" in state:
        hf = f"encoder.layers.{layer}"
        ours = f"enc_blocks/{layer}"
        _layer_norm(out, f"{ours}/ln_attn", state, f"{hf}.self_attn_layer_norm")
        _attention(out, f"{ours}/attn", state, f"{hf}.self_attn")
        _layer_norm(out, f"{ours}/ln_mlp", state, f"{hf}.final_layer_norm")
        _linear(out, f"{ours}/mlp_in", state, f"{hf}.fc1")
        _linear(out, f"{ours}/mlp_out", state, f"{hf}.fc2")
        layer += 1
    _layer_norm(out, "ln_enc", state, "encoder.layer_norm")

    out["tok_embed/table"] = state["decoder.embed_tokens.weight"]
    out["pos_embed"] = state["decoder.embed_positions.weight"]
    layer = 0
    while f"decoder.layers.{layer}.fc1.weight" in state:
        hf = f"decoder.layers.{layer}"
        ours = f"dec_blocks/{layer}"
        _layer_norm(out, f"{ours}/ln_attn", state, f"{hf}.self_attn_layer_norm")
        _attention(out, f"{ours}/attn", state, f"{hf}.self_attn")
        _layer_norm(out, f"{ours}/ln_cross", state,
                    f"{hf}.encoder_attn_layer_norm")
        _attention(out, f"{ours}/cross", state, f"{hf}.encoder_attn")
        _layer_norm(out, f"{ours}/ln_mlp", state, f"{hf}.final_layer_norm")
        _linear(out, f"{ours}/mlp_in", state, f"{hf}.fc1")
        _linear(out, f"{ours}/mlp_out", state, f"{hf}.fc2")
        layer += 1
    _layer_norm(out, "ln_dec", state, "decoder.layer_norm")
    return out


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("model_dir")
    parser.add_argument("out_dir")
    args = parser.parse_args()

    os.makedirs(args.out_dir, exist_ok=True)
    state = load_state_dict(args.model_dir)
    flat = convert(state)
    np.savez(os.path.join(args.out_dir, "weights.npz"),
             **{k: np.asarray(v, np.float32) for k, v in flat.items()})
    for name in ("vocab.json", "merges.txt"):
        src = os.path.join(args.model_dir, name)
        if os.path.exists(src):
            shutil.copy(src, os.path.join(args.out_dir, name))
        else:
            print(f"warning: {name} not found in {args.model_dir}",
                  file=sys.stderr)
    print(f"wrote {len(flat)} arrays to {args.out_dir}/weights.npz")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
