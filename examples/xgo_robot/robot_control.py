# XGO teleop client: keyboard drive + live camera + telemetry.
#
# The consumer half of the xgo example (reference:
# examples/xgo_robot/robot_control.py — 283 LoC teleop UI subscribing to
# the robot's video topic and calling its RPC surface).  The control
# logic lives in RobotControl (headless, testable); run_teleop wraps it
# in a curses loop that renders the camera as ASCII luminance plus the
# EC-mirrored telemetry.
#
# Run (against a live robot/sim on the same control plane):
#   python examples/xgo_robot/robot_control.py
# Self-test (robot + teleop in one process, no UI):
#   python examples/xgo_robot/robot_control.py --self-test

from __future__ import annotations

import os
import sys

# allow running straight from a source checkout
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from aiko_services_tpu import ProcessRuntime, Registrar
from aiko_services_tpu.actor import ActorDiscovery, get_remote_proxy
from aiko_services_tpu.elements.audio import decode_tensor
from aiko_services_tpu.service import ServiceFilter
from aiko_services_tpu.share import ECConsumer

MOVE_STEP = 10.0       # mm per keypress
TURN_STEP = 15.0       # degrees per keypress

# key -> (method, args) over the robot RPC surface
# (reference robot_control.py command map)
KEY_COMMANDS = {
    "w": ("move", ["forward", MOVE_STEP]),
    "s": ("move", ["backward", MOVE_STEP]),
    "a": ("move", ["left", MOVE_STEP]),
    "d": ("move", ["right", MOVE_STEP]),
    "q": ("turn", [-TURN_STEP]),
    "e": ("turn", [TURN_STEP]),
    "r": ("reset", []),
    "g": ("claw", [255]),
    "G": ("claw", [0]),
    "1": ("action", [1]),
    "2": ("action", [2]),
    "3": ("action", [3]),
}


class RobotControl:
    """Headless teleop model: discovery, RPC, video tail, telemetry."""

    def __init__(self, runtime):
        self.runtime = runtime
        self.robot_topic_path = None
        self.proxy = None
        self.telemetry: dict = {}
        self._consumer = None
        self.last_frame = None
        self.frames_seen = 0
        self._video_topic = None
        from xgo_robot import PROTOCOL_XGO
        self.discovery = ActorDiscovery(runtime)
        self.discovery.add_handler(
            self._robot_change, ServiceFilter(protocol=str(PROTOCOL_XGO)))

    # -- discovery ----------------------------------------------------------
    def _robot_change(self, event: str, fields) -> None:
        if event == "add" and self.proxy is None:
            self._attach(fields)
        elif event == "remove" and \
                fields.topic_path == self.robot_topic_path:
            self._detach()

    def _attach(self, fields) -> None:
        from xgo_robot import XgoRobot      # the RPC protocol surface
        self.robot_topic_path = fields.topic_path
        self.proxy = get_remote_proxy(
            self.runtime, f"{fields.topic_path}/in", XgoRobot)
        self._consumer = ECConsumer(self.runtime, self.telemetry,
                                    f"{fields.topic_path}/control")
        self._video_topic = f"{fields.topic_path}/video"
        self.runtime.add_message_handler(self._on_video,
                                         self._video_topic, binary=True)

    def _detach(self) -> None:
        if self._consumer is not None:
            self._consumer.terminate()
            self._consumer = None
        if self._video_topic is not None:
            self.runtime.remove_message_handler(self._on_video,
                                                self._video_topic)
            self._video_topic = None
        self.proxy = None
        self.robot_topic_path = None
        self.telemetry.clear()

    @property
    def connected(self) -> bool:
        return self.proxy is not None

    # -- video --------------------------------------------------------------
    def _on_video(self, _topic, payload) -> None:
        try:
            self.last_frame = decode_tensor(payload)
            self.frames_seen += 1
        except Exception:
            pass

    def start_video(self, rate: float = 10.0) -> None:
        if self.proxy is not None:
            self.proxy.video_start(rate)

    def stop_video(self) -> None:
        if self.proxy is not None:
            self.proxy.video_stop()

    # -- commands -----------------------------------------------------------
    def handle_key(self, key: str) -> bool:
        """Dispatch a keypress to the robot; True when it mapped."""
        command = KEY_COMMANDS.get(key)
        if command is None or self.proxy is None:
            return False
        method, args = command
        getattr(self.proxy, method)(*args)
        return True

    def status_lines(self) -> list:
        """Telemetry summary for any frontend."""
        if not self.connected:
            return ["searching for robot..."]
        lines = [f"robot: {self.robot_topic_path}",
                 f"video frames: {self.frames_seen}"]
        for key in ("battery", "action", "claw",
                    "pose.x", "pose.y", "pose.z"):
            flat = self.telemetry.get("pose", {}) \
                if key.startswith("pose.") else self.telemetry
            name = key.split(".")[-1] if key.startswith("pose.") else key
            if isinstance(flat, dict) and name in flat:
                lines.append(f"{key}: {flat[name]}")
            elif key in self.telemetry:
                lines.append(f"{key}: {self.telemetry[key]}")
        return lines

    def terminate(self) -> None:
        self._detach()
        self.discovery.cache.terminate()


_ASCII_RAMP = " .:-=+*#%@"


def frame_to_ascii(frame: np.ndarray, width: int = 64,
                   height: int = 20) -> list:
    """Downsample an HxWx3 frame to ASCII luminance rows (block max —
    point sampling would drop thin features like edges/markers)."""
    if frame is None:
        return ["(no video)"]
    grey = frame.mean(axis=2) if frame.ndim == 3 else frame
    y_edges = np.linspace(0, grey.shape[0], height + 1).astype(int)
    x_edges = np.linspace(0, grey.shape[1], width + 1).astype(int)
    rows = []
    for y0, y1 in zip(y_edges[:-1], y_edges[1:]):
        row = []
        for x0, x1 in zip(x_edges[:-1], x_edges[1:]):
            block = grey[y0:max(y1, y0 + 1), x0:max(x1, x0 + 1)]
            value = float(block.max()) if block.size else 0.0
            row.append(_ASCII_RAMP[int(value / 255.0 *
                                       (len(_ASCII_RAMP) - 1))])
        rows.append("".join(row))
    return rows


def run_teleop(runtime, tick: float = 0.03) -> None:
    """Blocking curses teleop loop (reference robot_control.py UI)."""
    import curses
    import time

    control = RobotControl(runtime)

    def loop(screen):
        curses.curs_set(0)
        screen.nodelay(True)
        video_on = False
        while True:
            for _ in range(8):
                runtime.event.step()
            key = screen.getch()
            if key in (27, ord("x")):
                break
            if key == ord("v"):
                (control.stop_video if video_on
                 else control.start_video)()
                video_on = not video_on
            elif key >= 0:
                control.handle_key(chr(key) if key < 256 else "")
            screen.erase()
            height, width = screen.getmaxyx()
            rows = frame_to_ascii(control.last_frame,
                                  width=min(64, width - 2),
                                  height=min(20, height - 10))
            for row, line in enumerate(rows[:height - 1]):
                screen.addnstr(row, 0, line, width - 1)
            for offset, line in enumerate(control.status_lines()):
                if len(rows) + offset < height - 2:
                    screen.addnstr(len(rows) + offset, 0, line, width - 1)
            footer = ("wasd move · q/e turn · g/G claw · 1-3 action · "
                      "r reset · v video · x quit")
            screen.addnstr(height - 1, 0, footer[:width - 1], width - 1,
                           curses.A_REVERSE)
            screen.refresh()
            time.sleep(tick)

    try:
        curses.wrapper(loop)
    finally:
        control.terminate()


def main() -> None:
    runtime = ProcessRuntime(name="robot_control").initialize()
    if "--self-test" in sys.argv:
        from xgo_robot import XgoRobot
        Registrar(runtime)
        robot = XgoRobot(runtime)
        control = RobotControl(runtime)
        runtime.event.run_until(lambda: control.connected, timeout=6.0)
        control.handle_key("w")
        control.handle_key("g")
        control.start_video(rate=50.0)
        runtime.event.run_until(
            lambda: control.frames_seen >= 3 and
            robot.ec_producer.get("claw") == 255, timeout=6.0)
        assert robot.ec_producer.get("pose.x") == MOVE_STEP
        ascii_rows = frame_to_ascii(control.last_frame)
        assert any(ch != " " for row in ascii_rows for ch in row)
        print(f"self-test ok: drove robot to pose.x={MOVE_STEP}, "
              f"claw=255, {control.frames_seen} frames, "
              f"ascii {len(ascii_rows)} rows")
        control.terminate()
        runtime.terminate()
        return
    run_teleop(runtime)


if __name__ == "__main__":
    main()
