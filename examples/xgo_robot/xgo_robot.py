# XGO robot actor: teleoperated quadruped with camera streaming and
# telemetry (reference: examples/xgo_robot/xgo_robot.py — 420 LoC robot
# actor with ~20 RPC methods, zlib video publish, battery telemetry,
# hardware mocked off-robot).
#
# The hardware layer is injected (XgoHardware protocol); off-robot the
# SimulatedXgo tracks commanded state so the full RPC surface, telemetry
# shares, and the camera tensor path run anywhere.  Run:
#   python examples/xgo_robot/xgo_robot.py --self-test

from __future__ import annotations

import os
import sys

# allow running straight from a source checkout
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import sys

import numpy as np

from aiko_services_tpu import Actor, ProcessRuntime, Registrar
from aiko_services_tpu.elements.audio import encode_tensor
from aiko_services_tpu.service import ServiceProtocol

PROTOCOL_XGO = ServiceProtocol("xgo_robot")


class SimulatedXgo:
    """Off-robot hardware stand-in (reference mocks with is_robot() gates,
    xgo_robot.py:86-89)."""

    def __init__(self):
        self.pose = {"x": 0.0, "y": 0.0, "z": 100.0}
        self.attitude = {"roll": 0.0, "pitch": 0.0, "yaw": 0.0}
        self.arm_position = {"arm_x": 0.0, "arm_z": 0.0}
        self.claw_grip = 0
        self.battery = 100
        self.action_id = 0
        self._camera_phase = 0

    def read_battery(self) -> int:
        self.battery = max(0, self.battery - 1)
        return self.battery

    def capture_image(self) -> np.ndarray:
        self._camera_phase += 1
        image = np.zeros((120, 160, 3), np.uint8)
        image[:, (self._camera_phase * 4) % 160] = 255
        return image


class XgoRobot(Actor):
    """The robot service: RPC surface + telemetry share + video publish."""

    def __init__(self, runtime, hardware=None, name: str = "xgo_robot"):
        super().__init__(runtime, name, PROTOCOL_XGO, share={
            "battery": 100, "action": 0, "claw": 0,
            "pose.x": 0.0, "pose.y": 0.0, "pose.z": 100.0,
        })
        self.hardware = hardware or SimulatedXgo()
        self.video_topic = f"{self.topic_path}/video"
        self._video_timer = None
        self._telemetry_timer = runtime.event.add_timer_handler(
            self._telemetry, 5.0)

    # -- motion RPC (reference: xgo_robot.py:93-120) ------------------------
    def action(self, action_id) -> None:
        self.hardware.action_id = int(action_id)
        self.ec_producer.update("action", int(action_id))

    def move(self, direction, distance) -> None:
        axis = "x" if direction in ("forward", "backward") else "y"
        sign = 1.0 if direction in ("forward", "left") else -1.0
        self.hardware.pose[axis] += sign * float(distance)
        self.ec_producer.update(f"pose.{axis}",
                                self.hardware.pose[axis])

    def turn(self, degrees) -> None:
        self.hardware.attitude["yaw"] = \
            (self.hardware.attitude["yaw"] + float(degrees)) % 360.0

    def attitude(self, roll, pitch, yaw) -> None:
        self.hardware.attitude.update(roll=float(roll), pitch=float(pitch),
                                      yaw=float(yaw))

    def translation(self, x, y, z) -> None:
        self.hardware.pose.update(x=float(x), y=float(y), z=float(z))
        for axis in ("x", "y", "z"):
            self.ec_producer.update(f"pose.{axis}",
                                    self.hardware.pose[axis])

    def arm(self, arm_x, arm_z) -> None:
        self.hardware.arm_position.update(arm_x=float(arm_x),
                                          arm_z=float(arm_z))

    def claw(self, grip) -> None:
        self.hardware.claw_grip = int(grip)
        self.ec_producer.update("claw", int(grip))

    def reset(self) -> None:
        self.translation(0.0, 0.0, 100.0)
        self.attitude(0.0, 0.0, 0.0)

    def stop(self) -> None:
        if self._video_timer is not None:
            self.video_stop()
        self.runtime.event.remove_timer_handler(self._telemetry_timer)
        super().stop()

    # -- camera (reference: _publish_image zlib+np.save) --------------------
    def video_start(self, rate=10.0) -> None:
        if self._video_timer is not None:
            return

        def publish_frame():
            image = self.hardware.capture_image()
            self.runtime.publish(self.video_topic, encode_tensor(image))

        self._video_timer = self.runtime.event.add_timer_handler(
            publish_frame, 1.0 / float(rate))

    def video_stop(self) -> None:
        if self._video_timer is not None:
            self.runtime.event.remove_timer_handler(self._video_timer)
            self._video_timer = None

    # -- telemetry ----------------------------------------------------------
    def _telemetry(self) -> None:
        self.ec_producer.update("battery", self.hardware.read_battery())


def main() -> None:
    runtime = ProcessRuntime(name="xgo_robot").initialize()
    if "--self-test" in sys.argv:
        from aiko_services_tpu.elements.audio import decode_tensor
        Registrar(runtime)
        robot = XgoRobot(runtime)
        frames = []
        runtime.add_message_handler(
            lambda _t, payload: frames.append(decode_tensor(payload)),
            robot.video_topic, binary=True)
        runtime.event.run_until(lambda: runtime.registrar is not None,
                                timeout=6.0)
        runtime.publish(robot.topic_in, "(move forward 25)")
        runtime.publish(robot.topic_in, "(claw 128)")
        robot.video_start(rate=50.0)
        runtime.event.run_until(lambda: len(frames) >= 3, timeout=6.0)
        assert robot.ec_producer.get("pose.x") == 25.0
        assert robot.ec_producer.get("claw") == 128
        print(f"self-test ok: pose.x=25.0 claw=128 "
              f"{len(frames)} video frames {frames[0].shape}")
        runtime.terminate()
        return
    XgoRobot(runtime)
    runtime.run()


if __name__ == "__main__":
    main()
