# Hello-world actor (reference: examples/aloha_honua/aloha_honua_0.py).
#
# Run (two terminals, or one with --self-test):
#   aiko_tpu registrar &
#   python examples/aloha_honua/aloha_honua.py
#   # then publish "(aloha Pele)" to the actor's topic_in
#
# With --self-test everything (registrar, actor, caller) runs in one
# process on the in-memory broker — no external services needed.

from __future__ import annotations

import os
import sys

# allow running straight from a source checkout
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import sys

from aiko_services_tpu import Actor, ProcessRuntime, Registrar


class AlohaHonua(Actor):
    def __init__(self, runtime, name: str = "aloha_honua"):
        super().__init__(runtime, name, share={"greetings": 0})

    def aloha(self, name: str) -> None:
        count = self.ec_producer.get("greetings", 0) + 1
        self.ec_producer.update("greetings", count)
        self.logger.info("Aloha %s! (%d greetings)", name, count)
        print(f"Aloha {name}!")


def main() -> None:
    runtime = ProcessRuntime(name="aloha_honua").initialize()
    if "--self-test" in sys.argv:
        Registrar(runtime)
        actor = AlohaHonua(runtime)
        runtime.event.run_until(lambda: runtime.registrar is not None,
                                timeout=6.0)
        runtime.publish(actor.topic_in, "(aloha Pele)")
        runtime.event.run_until(
            lambda: actor.ec_producer.get("greetings", 0) >= 1,
            timeout=6.0)
        print("self-test ok:", actor.ec_producer.get("greetings"),
              "greeting(s)")
        runtime.terminate()
        return
    AlohaHonua(runtime)
    runtime.run()


if __name__ == "__main__":
    main()
