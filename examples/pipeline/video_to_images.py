#!/usr/bin/env python3
"""Explode a video file into numbered images through the pipeline
(reference parity: examples/pipeline/video_to_images.py, which runs
VideoReadFile → ImageOverlay → ImageWriteFile on the 2020 pipeline).

Usage:
    python examples/pipeline/video_to_images.py input.mp4 \
        "out/image_{frame:06d}.jpg" [--overlay]

Runs flat-out (rate=0 semantics: frames post as fast as they complete),
entirely in-process on the memory transport.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("video")
    parser.add_argument("image_pattern",
                        help='e.g. "out/image_{frame:06d}.jpg"')
    parser.add_argument("--overlay", action="store_true",
                        help="draw the frame-id overlay before writing")
    parser.add_argument("--rate", type=float, default=200.0)
    args = parser.parse_args()

    from aiko_services_tpu.event import EventEngine
    from aiko_services_tpu.pipeline import (
        FrameOutput, Pipeline, PipelineElement, parse_pipeline_definition)
    from aiko_services_tpu.process import ProcessRuntime
    from aiko_services_tpu.transport.memory import (MemoryBroker,
                                                    MemoryMessage)

    os.makedirs(os.path.dirname(args.image_pattern) or ".", exist_ok=True)

    class PE_NumberedWrite(PipelineElement):
        """ImageWriteFile with the reference's numbered-pathname
        behavior (image_{:06d}.jpg)."""

        def process_frame(self, frame, image=None, **_):
            from PIL import Image
            import numpy as np
            pathname = args.image_pattern.format(frame=frame.frame_id)
            Image.fromarray(np.asarray(image).astype("uint8")).save(
                pathname)
            return FrameOutput(True, {"pathname": pathname})

    engine = EventEngine()
    broker = MemoryBroker()
    runtime = ProcessRuntime(
        name="video_to_images", engine=engine,
        transport_factory=lambda on_message, lt, lp, lr: MemoryMessage(
            on_message=on_message, broker=broker, lwt_topic=lt,
            lwt_payload=lp, lwt_retain=lr)).initialize()

    graph = "(PE_VideoReadFile (PE_ImageAnnotate (PE_NumberedWrite)))" \
        if args.overlay else "(PE_VideoReadFile (PE_NumberedWrite))"
    elements = [
        {"name": "PE_VideoReadFile", "input": [],
         "output": [{"name": "image"}]},
        {"name": "PE_NumberedWrite", "input": [{"name": "image"}],
         "output": [{"name": "pathname"}]},
    ]
    if args.overlay:
        elements.insert(1, {"name": "PE_ImageAnnotate",
                            "input": [{"name": "image"}],
                            "output": [{"name": "image"}]})
    pipeline = Pipeline(
        runtime,
        parse_pipeline_definition({
            "version": 0, "name": "p_v2i", "runtime": "python",
            "graph": [graph],
            "parameters": {"PE_VideoReadFile.pathname": args.video,
                           "PE_VideoReadFile.rate": args.rate},
            "elements": elements,
        }),
        element_classes={"PE_NumberedWrite": PE_NumberedWrite},
        stream_lease_time=0)

    written = []
    pipeline.add_frame_handler(lambda frame: written.append(frame))
    pipeline.create_stream("v", lease_time=0)
    # PE_VideoReadFile stops creating frames at EOF; run until quiet
    import time
    last = -1
    while True:
        engine.run_until(lambda: False, timeout=1.0)
        if len(written) == last:
            break
        last = len(written)
    pipeline.destroy_stream("v")
    runtime.terminate()
    print(f"wrote {len(written)} images to {args.image_pattern}")
    return 0 if written else 1


if __name__ == "__main__":
    raise SystemExit(main())
