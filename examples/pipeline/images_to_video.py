#!/usr/bin/env python3
"""Assemble numbered images into a video file through the pipeline
(reference parity: examples/pipeline/images_to_video.py —
ImageReadFile → VideoWriteFile on the 2020 pipeline).

Usage:
    python examples/pipeline/images_to_video.py \
        "in/image_{frame:06d}.jpg" output.mp4 [--fps 29.97]
"""

import argparse
import glob
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("image_pattern",
                        help='e.g. "in/image_{frame:06d}.jpg"')
    parser.add_argument("video")
    parser.add_argument("--fps", type=float, default=29.97)
    args = parser.parse_args()

    from aiko_services_tpu.event import EventEngine
    from aiko_services_tpu.pipeline import Pipeline, \
        parse_pipeline_definition
    from aiko_services_tpu.process import ProcessRuntime
    from aiko_services_tpu.transport.memory import (MemoryBroker,
                                                    MemoryMessage)

    # expand the numbered pattern to the existing, sorted input files
    wildcard = re.sub(r"\{frame[^}]*\}", "*", args.image_pattern)
    pathnames = sorted(glob.glob(wildcard))
    if not pathnames:
        print(f"no images match {wildcard}", file=sys.stderr)
        return 1

    engine = EventEngine()
    broker = MemoryBroker()
    runtime = ProcessRuntime(
        name="images_to_video", engine=engine,
        transport_factory=lambda on_message, lt, lp, lr: MemoryMessage(
            on_message=on_message, broker=broker, lwt_topic=lt,
            lwt_payload=lp, lwt_retain=lr)).initialize()

    pipeline = Pipeline(
        runtime,
        parse_pipeline_definition({
            "version": 0, "name": "p_i2v", "runtime": "python",
            "graph": ["(PE_ImageReadFile (PE_VideoWriteFile))"],
            "parameters": {"PE_VideoWriteFile.pathname": args.video,
                           "PE_VideoWriteFile.rate": args.fps},
            "elements": [
                {"name": "PE_ImageReadFile", "input": [],
                 "output": [{"name": "image"}]},
                {"name": "PE_VideoWriteFile",
                 "input": [{"name": "image"}], "output": []},
            ],
        }),
        stream_lease_time=0)

    done = []
    pipeline.add_frame_handler(done.append)
    pipeline.create_stream("v", lease_time=0)
    for pathname in pathnames:
        pipeline.post("process_frame", "v", {"pathname": pathname})
    engine.run_until(lambda: len(done) >= len(pathnames), timeout=600.0)
    pipeline.destroy_stream("v")          # flushes/releases the writer
    runtime.terminate()
    print(f"wrote {len(done)} frames to {args.video}")
    return 0 if len(done) == len(pathnames) else 1


if __name__ == "__main__":
    raise SystemExit(main())
